"""BASELINE configs 2 & 3: ImageNet ResNet-50 with amp O2 (+FusedAdam) and
data-parallel + SyncBatchNorm.

Port of ``examples/imagenet/main_amp.py`` / ``tests/L1/common/main_amp.py``:
the flag surface (``--opt-level``, ``--loss-scale``,
``--keep-batchnorm-fp32``, ``--fused-adam``, ``--sync-bn``, ``--prof``,
``--deterministic``) and the throughput/AverageMeter reporting carry over;
process-group DDP becomes a ``shard_map`` over the ``("data",)`` mesh with
:class:`apex_tpu.parallel.DistributedDataParallel` reduction.

Data is synthetic by default (this environment has no ImageNet); plug a real
loader into ``data_iter`` for convergence runs (LR schedule per the
reference "should yield 76%": 0.1·B/256, /10 at epochs 30/60/80).
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ARCHS
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import (
    DistributedDataParallel,
    convert_syncbn_model,
    data_parallel_mesh,
)
from apex_tpu.utils import maybe_print


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="resnet50",
               choices=sorted(ARCHS))
    p.add_argument("-b", "--batch-size", type=int, default=128,
                   help="per-device batch")
    p.add_argument("--lr", type=float, default=None,
                   help="default: 0.1 (SGD) or 1e-3 (FusedAdam)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--fused-adam", action="store_true")
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--dp", action="store_true",
                   help="data-parallel over all visible devices")
    p.add_argument("--prof", type=int, default=0,
                   help="profile N steps then exit (reference --prof)")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save a checkpoint every --checkpoint-freq steps "
                        "(reference epoch checkpointing, "
                        "main_amp.py:170-185)")
    p.add_argument("--checkpoint-freq", type=int, default=50)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in "
                        "--checkpoint-dir (reference --resume)")
    return p.parse_args()


class AverageMeter:
    """(reference ``main_amp.py:336-372``)"""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = self.sum = self.count = self.avg = 0.0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


def synthetic_batch(key, batch, size):
    x = jax.random.normal(key, (batch, size, size, 3), jnp.float32)
    y = jax.random.randint(key, (batch,), 0, 1000)
    return x, y


def main():
    args = parse_args()
    if args.deterministic:
        seed = 0
    else:
        seed = int(time.time())

    n_dev = len(jax.devices()) if args.dp else 1
    model = ARCHS[args.arch]()
    if args.sync_bn:
        if not args.dp:
            raise SystemExit("--sync-bn requires --dp: the \"data\" mesh "
                             "axis SyncBatchNorm reduces over only exists "
                             "under the data-parallel shard_map")
        model = convert_syncbn_model(model, axis_name="data")
        maybe_print("using SyncBatchNorm over the data axis")

    x0, _ = synthetic_batch(jax.random.PRNGKey(0), 2, args.image_size)
    variables = model.init(jax.random.PRNGKey(seed), x0, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    if args.fused_adam:
        tx = FusedAdam(lr=args.lr if args.lr is not None else 1e-3)
    else:
        tx = optax.sgd(args.lr if args.lr is not None else 0.1, momentum=0.9)
    a = amp.initialize(optimizer=tx, opt_level=args.opt_level,
                       loss_scale=args.loss_scale,
                       keep_batchnorm_fp32=args.keep_batchnorm_fp32)
    state = a.init(params)

    def make_loss_fn(stats):
        def loss_fn(p, x, y):
            logits, mut = model.apply({"params": p, "batch_stats": stats},
                                      x, train=True, mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
            return loss, mut["batch_stats"]
        return loss_fn

    if args.dp:
        mesh = data_parallel_mesh()
        ddp = DistributedDataParallel(axis_name="data")

        def sharded(s, stats, x, y):
            inner = amp.make_train_step(a, make_loss_fn(stats),
                                        axis_name="data",
                                        reduce_fn=ddp.reduce, has_aux=True)
            s2, m = inner(s, x, y)
            # SyncBN already produces identical stats on every device; for
            # local BN this averages the per-device running stats so one
            # replicated copy carries forward (the reference checkpoints
            # rank 0's copy instead).
            stats2 = jax.lax.pmean(m["aux"], "data")
            return (s2, stats2, jax.lax.pmean(m["loss"], "data"),
                    m["loss_scale"])

        step = jax.jit(jax.shard_map(
            sharded, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P())))
    else:
        def step(s, stats, x, y):
            inner = amp.make_train_step(a, make_loss_fn(stats),
                                        has_aux=True)
            s2, m = inner(s, x, y)
            return s2, m["aux"], m["loss"], m["loss_scale"]

        step = jax.jit(step)

    mgr = None
    start_step = 0
    if args.checkpoint_dir:
        from apex_tpu.checkpoint import CheckpointManager
        mgr = CheckpointManager(args.checkpoint_dir)
        if args.resume and mgr.latest_step() is not None:
            state, extras = mgr.restore(state,
                                        extras={"batch_stats": batch_stats})
            batch_stats = extras["batch_stats"]
            start_step = mgr.latest_step() + 1
            maybe_print(f"resumed from step {mgr.latest_step()}")

    global_batch = args.batch_size * n_dev
    steps = args.prof or args.steps
    if args.prof:
        # reference --prof: nvtx ranges + early exit (main_amp.py:63-64);
        # here a full XProf capture of the profiled steps.
        from apex_tpu.utils import profiler_start
        profiler_start("/tmp/apex_tpu_trace")
        maybe_print(f"profiling {steps} steps -> /tmp/apex_tpu_trace")
    losses = AverageMeter()
    # Explicit span bookkeeping: the loss is fetched only at print
    # boundaries (a per-step device fetch would gate the async pipeline on
    # host round-trips — measured 5x throughput loss over the tunneled
    # transport; the reference synced per step because eager torch already
    # had).  The first span is compilation and stays out of the averages.
    last_t = time.time()
    last_i = start_step - 1
    warm_t0 = warm_i0 = None
    inst = 0.0
    for i in range(start_step, steps):
        kx = jax.random.PRNGKey(seed + i + 1)
        x, y = synthetic_batch(kx, global_batch, args.image_size)
        state, batch_stats, loss, scale = step(state, batch_stats, x, y)
        if mgr is not None and (i + 1) % args.checkpoint_freq == 0:
            mgr.save(i, state, extras={"batch_stats": batch_stats})
        if i % args.print_freq == 0 or i == steps - 1:
            loss = float(loss)          # sync point
            now = time.time()
            span = i - last_i
            inst = global_batch * span / max(now - last_t, 1e-9)
            losses.update(loss, global_batch)
            if warm_t0 is None:
                warm_t0, warm_i0 = now, i
                avg = inst
            else:
                avg = (global_batch * (i - warm_i0)
                       / max(now - warm_t0, 1e-9))
            maybe_print(
                f"step {i:4d}  loss {losses.val:.4f} ({losses.avg:.4f})  "
                f"scale {float(scale):.0f}  "
                f"{inst:.0f} img/s ({avg:.0f} avg)")
            last_t, last_i = now, i
    if args.prof:
        from apex_tpu.utils import profiler_stop
        profiler_stop()
    if mgr is not None:
        mgr.wait()  # commit any in-flight async checkpoint
    if warm_t0 is not None and last_i > warm_i0:
        speed = global_batch * (last_i - warm_i0) / max(last_t - warm_t0,
                                                        1e-9)
    else:  # a single boundary (e.g. --steps 1): the compile-span rate
        speed = inst
    maybe_print(f"Speed: {speed:.1f} img/s total (post-warmup)")


if __name__ == "__main__":
    main()
