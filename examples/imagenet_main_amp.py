"""BASELINE configs 2 & 3: ImageNet ResNet-50 with amp O2 (+FusedAdam) and
data-parallel + SyncBatchNorm.

Port of ``examples/imagenet/main_amp.py`` / ``tests/L1/common/main_amp.py``:
the flag surface (``--opt-level``, ``--loss-scale``,
``--keep-batchnorm-fp32``, ``--fused-adam``, ``--sync-bn``, ``--prof``,
``--deterministic``) and the throughput/AverageMeter reporting carry over;
process-group DDP becomes a ``shard_map`` over the ``("data",)`` mesh with
:class:`apex_tpu.parallel.DistributedDataParallel` reduction.

The full train→validate epoch structure of the reference carries over:
``validate()`` with loss/prec@1/prec@5 AverageMeters
(``main_amp.py:439-460``), ``accuracy(output, target, topk)``
(``:475-489``), best-prec@1 tracking with an ``is_best`` checkpoint marker
(``:170-185, 244-254``), and the step-decay + warmup LR schedule
(``adjust_learning_rate``, ``:462-478``).

Data: ``--data synthetic`` (default; this environment has no ImageNet) or
``--data digits`` — the sklearn handwritten-digits set (1797 real 8x8
images, 10 classes), the real-data convergence path for this environment.
An ImageNet-layout directory can be wired the same way: implement
``load_xxx()`` returning ``(train_x, train_y, val_x, val_y)`` NHWC float32
arrays and register it in ``DATASETS``.
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ARCHS
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import (
    DistributedDataParallel,
    convert_syncbn_model,
    data_parallel_mesh,
)
from apex_tpu.utils import maybe_print
from apex_tpu.utils.jax_compat import shard_map


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="resnet50",
               choices=sorted(ARCHS))
    p.add_argument("-b", "--batch-size", type=int, default=128,
                   help="per-device batch")
    p.add_argument("--lr", type=float, default=None,
                   help="default: 0.1 (SGD) or 1e-3 (FusedAdam)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--fused-adam", action="store_true")
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--dp", action="store_true",
                   help="data-parallel over all visible devices")
    p.add_argument("--prof", type=int, default=0,
                   help="profile N steps then exit (reference --prof)")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save a checkpoint every --checkpoint-freq steps "
                        "(reference epoch checkpointing, "
                        "main_amp.py:170-185)")
    p.add_argument("--checkpoint-freq", type=int, default=50)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in "
                        "--checkpoint-dir (reference --resume)")
    p.add_argument("--data-pipeline", default="device",
                   choices=["device", "host"],
                   help="device: batches generated device-resident "
                        "(fastest); host: uint8 numpy batches streamed "
                        "through apex_tpu.data.prefetch_to_device with "
                        "on-device normalization — the reference "
                        "data_prefetcher pattern (main_amp.py:256-290)")
    p.add_argument("--data", default="synthetic",
                   choices=["synthetic", "digits"],
                   help="synthetic stream, or the sklearn digits set "
                        "(real data: 1797 8x8 images, 10 classes)")
    p.add_argument("--epochs", type=int, default=30,
                   help="epochs over real data (--data digits); synthetic "
                        "mode uses --steps instead")
    p.add_argument("--warmup-epochs", type=int, default=5,
                   help="linear LR warmup (reference adjust_learning_rate)")
    p.add_argument("--evaluate", action="store_true",
                   help="run validation only (reference --evaluate)")
    p.add_argument("--target-top1", type=float, default=None,
                   help="exit nonzero unless final best prec@1 reaches "
                        "this (convergence-proof runs)")
    return p.parse_args()


class AverageMeter:
    """(reference ``main_amp.py:336-372``)"""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = self.sum = self.count = self.avg = 0.0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


def synthetic_batch(key, batch, size):
    x = jax.random.normal(key, (batch, size, size, 3), jnp.float32)
    y = jax.random.randint(key, (batch,), 0, 1000)
    return x, y


def load_digits(image_size):
    """sklearn handwritten digits as NHWC float32: 1437 train / 360 val
    (deterministic split), grey replicated to 3 channels, resized to
    ``image_size`` — the smallest *real* image-classification set available
    in this environment."""
    from sklearn.datasets import load_digits as _ld
    d = _ld()
    x = d.images.astype(np.float32) / 16.0
    x = (x - 0.5) / 0.5
    x = np.repeat(x[..., None], 3, axis=-1)            # (N, 8, 8, 3)
    if image_size != 8:
        x = np.asarray(jax.image.resize(
            jnp.asarray(x), (x.shape[0], image_size, image_size, 3),
            "nearest"))
    y = d.target.astype(np.int32)
    perm = np.random.RandomState(0).permutation(len(y))
    x, y = x[perm], y[perm]
    n_val = 360
    return x[:-n_val], y[:-n_val], x[-n_val:], y[-n_val:], 10


DATASETS = {"digits": load_digits}


def accuracy(logits, target, topk=(1,)):
    """precision@k over a logits batch (reference ``main_amp.py:475-489``)."""
    maxk = max(topk)
    _, pred = jax.lax.top_k(logits, maxk)              # (B, maxk)
    correct = pred == target[:, None]
    return [100.0 * jnp.sum(correct[:, :k]) / target.shape[0] for k in topk]


def make_validate(model, a, eval_batch):
    """The reference ``validate()`` loop (``main_amp.py:439-460``): eval-mode
    forward over the val set, loss/prec@1/prec@5 AverageMeters, returns
    ``prec@1``."""

    @jax.jit
    def eval_step(p, stats, x, y):
        # O2/O3 policy input cast (training does this inside make_train_step)
        if a.properties.cast_model_dtype is not None:
            x = x.astype(a.properties.cast_model_dtype)
        logits = model.apply({"params": p, "batch_stats": stats}, x,
                             train=False).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
        prec1, prec5 = accuracy(logits, y, (1, 5))
        return loss, prec1, prec5

    def validate(state, batch_stats, val_x, val_y, print_freq=10):
        losses, top1, top5 = AverageMeter(), AverageMeter(), AverageMeter()
        p = a.model_params(state)
        n = (len(val_y) // eval_batch) * eval_batch
        t0 = time.time()
        for j, i in enumerate(range(0, n, eval_batch)):
            x = jnp.asarray(val_x[i:i + eval_batch])
            y = jnp.asarray(val_y[i:i + eval_batch])
            loss, p1, p5 = eval_step(p, batch_stats, x, y)
            losses.update(float(loss), eval_batch)
            top1.update(float(p1), eval_batch)
            top5.update(float(p5), eval_batch)
            if j % print_freq == 0:
                maybe_print(f"Test: [{j}/{n // eval_batch}]  "
                            f"loss {losses.val:.4f} ({losses.avg:.4f})  "
                            f"Prec@1 {top1.val:.3f} ({top1.avg:.3f})  "
                            f"Prec@5 {top5.val:.3f} ({top5.avg:.3f})")
        maybe_print(f" * Prec@1 {top1.avg:.3f} Prec@5 {top5.avg:.3f}  "
                    f"({n / max(time.time() - t0, 1e-9):.0f} img/s)")
        return top1.avg

    return validate


def make_lr_schedule(base_lr, len_epoch, epochs_warmup):
    """Reference ``adjust_learning_rate`` (``main_amp.py:462-478``): /10 at
    epochs 30/60/80 plus linear warmup over the first ``epochs_warmup``
    epochs, expressed as an optax-style ``step -> lr`` schedule."""

    def lr(global_step):
        e = global_step // len_epoch
        factor = e // 30 + jnp.where(e >= 80, 1, 0)
        out = base_lr * jnp.power(0.1, factor.astype(jnp.float32))
        warm = base_lr * (1.0 + global_step) / (epochs_warmup * len_epoch)
        return jnp.where(e < epochs_warmup, jnp.minimum(warm, out), out)

    return lr


def train_real(args, state, batch_stats, step, validate, mgr,
               train_x, train_y, val_x, val_y, global_batch,
               best_prec1, seed, start_step):
    """Epoch-structured train→validate loop over real data — the reference's
    ``for epoch: train(...); prec1 = validate(...); save_checkpoint(...,
    is_best)`` skeleton (``main_amp.py:170-185, 244-254``)."""
    import json

    len_epoch = max(len(train_y) // global_batch, 1)
    if args.prof:
        # reference --prof semantics (profile N steps, then exit) on the
        # real-data path: XProf capture of the first N steps of epoch 0
        from apex_tpu.utils import profiler_start, profiler_stop
        perm = np.random.RandomState(seed + 1000).permutation(len(train_y))
        profiler_start("/tmp/apex_tpu_trace")
        maybe_print(f"profiling {args.prof} steps -> /tmp/apex_tpu_trace")
        for b in range(args.prof):
            idx = perm[(b % len_epoch) * global_batch:][:global_batch]
            if len(idx) < global_batch:
                idx = np.concatenate([idx, perm[:global_batch - len(idx)]])
            state, batch_stats, loss, _ = step(
                state, batch_stats, jnp.asarray(train_x[idx]),
                jnp.asarray(train_y[idx]))
        float(loss)
        profiler_stop()
        return

    start_epoch = start_step // len_epoch
    for epoch in range(start_epoch, args.epochs):
        perm = np.random.RandomState(seed + 1000 + epoch).permutation(
            len(train_y))
        t0 = time.time()
        loss = scale = None
        for b in range(len_epoch):
            idx = perm[b * global_batch:(b + 1) * global_batch]
            if len(idx) < global_batch:   # static shapes: wrap the tail
                idx = np.concatenate([idx, perm[:global_batch - len(idx)]])
            x, y = jnp.asarray(train_x[idx]), jnp.asarray(train_y[idx])
            state, batch_stats, loss, scale = step(state, batch_stats, x, y)
        loss = float(loss)                # sync once per epoch
        speed = len_epoch * global_batch / max(time.time() - t0, 1e-9)
        maybe_print(f"Epoch {epoch:3d}  loss {loss:.4f}  "
                    f"scale {float(scale):.0f}  {speed:.0f} img/s")
        prec1 = validate(state, batch_stats, val_x, val_y)
        is_best = prec1 > best_prec1
        best_prec1 = max(prec1, best_prec1)
        if mgr is not None:
            mgr.save((epoch + 1) * len_epoch - 1, state,
                     extras={"batch_stats": batch_stats,
                             "best_prec1": jnp.asarray(best_prec1,
                                                       jnp.float32)})
            if is_best:
                # the reference copies checkpoint.pth.tar -> model_best;
                # the durable manager keeps whole step dirs, so record
                # WHICH step is best
                with open(os.path.join(args.checkpoint_dir,
                                       "best.json"), "w") as f:
                    json.dump({"step": (epoch + 1) * len_epoch - 1,
                               "epoch": epoch, "prec1": best_prec1}, f)
    if mgr is not None:
        mgr.wait()
    maybe_print(f"Best Prec@1 {best_prec1:.3f}")
    if args.target_top1 is not None and best_prec1 < args.target_top1:
        raise SystemExit(f"best prec@1 {best_prec1:.3f} below target "
                         f"{args.target_top1}")


def main():
    args = parse_args()
    if args.deterministic:
        seed = 0
    else:
        seed = int(time.time())

    n_dev = len(jax.devices()) if args.dp else 1

    real_data = args.data != "synthetic"
    if real_data and args.data_pipeline == "host":
        # fail loudly rather than silently measuring the device path:
        # the digits set is staged once (it fits on chip), so there is
        # no host stream to exercise there
        raise SystemExit("--data-pipeline host applies to --data "
                         "synthetic only; digits is device-staged")
    num_classes = 1000
    if real_data:
        train_x, train_y, val_x, val_y, num_classes = \
            DATASETS[args.data](args.image_size)
        maybe_print(f"{args.data}: {len(train_y)} train / {len(val_y)} val "
                    f"images, {num_classes} classes")
    model = ARCHS[args.arch](num_classes=num_classes)
    if args.sync_bn:
        if not args.dp:
            raise SystemExit("--sync-bn requires --dp: the \"data\" mesh "
                             "axis SyncBatchNorm reduces over only exists "
                             "under the data-parallel shard_map")
        model = convert_syncbn_model(model, axis_name="data")
        maybe_print("using SyncBatchNorm over the data axis")

    x0, _ = synthetic_batch(jax.random.PRNGKey(0), 2, args.image_size)
    variables = model.init(jax.random.PRNGKey(seed), x0, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    global_batch = args.batch_size * n_dev
    base_lr = args.lr if args.lr is not None else \
        (1e-3 if args.fused_adam else 0.1)
    if real_data:
        len_epoch = max(len(train_y) // global_batch, 1)
        lr = make_lr_schedule(base_lr, len_epoch, args.warmup_epochs)
    else:
        lr = base_lr
    if args.fused_adam:
        tx = FusedAdam(lr=lr)
    else:
        tx = optax.sgd(lr, momentum=0.9)
    a = amp.initialize(optimizer=tx, opt_level=args.opt_level,
                       loss_scale=args.loss_scale,
                       keep_batchnorm_fp32=args.keep_batchnorm_fp32)
    state = a.init(params)

    def make_loss_fn(stats):
        def loss_fn(p, x, y):
            logits, mut = model.apply({"params": p, "batch_stats": stats},
                                      x, train=True, mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
            return loss, mut["batch_stats"]
        return loss_fn

    if args.dp:
        mesh = data_parallel_mesh()
        ddp = DistributedDataParallel(axis_name="data")

        def sharded(s, stats, x, y):
            inner = amp.make_train_step(a, make_loss_fn(stats),
                                        axis_name="data",
                                        reduce_fn=ddp.reduce, has_aux=True)
            s2, m = inner(s, x, y)
            # SyncBN already produces identical stats on every device; for
            # local BN this averages the per-device running stats so one
            # replicated copy carries forward (the reference checkpoints
            # rank 0's copy instead).
            stats2 = jax.lax.pmean(m["aux"], "data")
            return (s2, stats2, jax.lax.pmean(m["loss"], "data"),
                    m["loss_scale"])

        step = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P())))
    else:
        def step(s, stats, x, y):
            inner = amp.make_train_step(a, make_loss_fn(stats),
                                        has_aux=True)
            s2, m = inner(s, x, y)
            return s2, m["aux"], m["loss"], m["loss_scale"]

        step = jax.jit(step)

    mgr = None
    start_step = 0
    best_prec1 = 0.0
    if args.checkpoint_dir:
        from apex_tpu.checkpoint import CheckpointManager
        mgr = CheckpointManager(args.checkpoint_dir)
        if args.resume and mgr.latest_step() is not None:
            state, extras = mgr.restore(
                state, extras={"batch_stats": batch_stats,
                               "best_prec1": jnp.zeros((), jnp.float32)})
            batch_stats = extras["batch_stats"]
            best_prec1 = float(extras["best_prec1"])
            start_step = mgr.latest_step() + 1
            maybe_print(f"resumed from step {mgr.latest_step()} "
                        f"(best prec@1 {best_prec1:.3f})")

    if real_data:
        # largest eval batch that divides the val set: static shapes, no
        # dropped or padded samples
        eval_b = max(b for b in range(1, min(args.batch_size,
                                             len(val_y)) + 1)
                     if len(val_y) % b == 0)
        validate = make_validate(model, a, eval_b)

    if args.evaluate:
        if not real_data:
            raise SystemExit("--evaluate requires real data (--data digits)")
        validate(state, batch_stats, val_x, val_y)
        return

    if real_data:
        train_real(args, state, batch_stats, step, validate, mgr,
                   train_x, train_y, val_x, val_y, global_batch,
                   best_prec1, seed, start_step)
        return

    steps = args.prof or args.steps
    if args.prof:
        # reference --prof: nvtx ranges + early exit (main_amp.py:63-64);
        # here a full XProf capture of the profiled steps.
        from apex_tpu.utils import profiler_start
        profiler_start("/tmp/apex_tpu_trace")
        maybe_print(f"profiling {steps} steps -> /tmp/apex_tpu_trace")
    losses = AverageMeter()
    # Explicit span bookkeeping: the loss is fetched only at print
    # boundaries (a per-step device fetch would gate the async pipeline on
    # host round-trips — measured 5x throughput loss over the tunneled
    # transport; the reference synced per step because eager torch already
    # had).  The first span is compilation and stays out of the averages.
    last_t = time.time()
    last_i = start_step - 1
    warm_t0 = warm_i0 = None
    inst = 0.0
    if args.data_pipeline == "host":
        from apex_tpu.data import (host_synthetic_loader, normalize_uint8,
                                   prefetch_to_device)
        sharding = None
        if args.dp:
            from jax.sharding import NamedSharding
            sharding = NamedSharding(mesh, P("data"))
        batches = prefetch_to_device(
            host_synthetic_loader(steps - start_step, global_batch,
                                  args.image_size, seed),
            lookahead=2, sharding=sharding, transform=normalize_uint8)
        maybe_print("host-streamed input pipeline: uint8 numpy batches, "
                    "H2D + on-device normalize overlapped (lookahead 2)")
    else:
        def _device_batches():
            for j in range(start_step, steps):
                kx = jax.random.PRNGKey(seed + j + 1)
                yield synthetic_batch(kx, global_batch, args.image_size)
        batches = _device_batches()
    for i, (x, y) in zip(range(start_step, steps), batches):
        state, batch_stats, loss, scale = step(state, batch_stats, x, y)
        if mgr is not None and (i + 1) % args.checkpoint_freq == 0:
            mgr.save(i, state,
                     extras={"batch_stats": batch_stats,
                             "best_prec1": jnp.zeros((), jnp.float32)})
        if i % args.print_freq == 0 or i == steps - 1:
            loss = float(loss)          # sync point
            now = time.time()
            span = i - last_i
            inst = global_batch * span / max(now - last_t, 1e-9)
            losses.update(loss, global_batch)
            if warm_t0 is None:
                warm_t0, warm_i0 = now, i
                avg = inst
            else:
                avg = (global_batch * (i - warm_i0)
                       / max(now - warm_t0, 1e-9))
            maybe_print(
                f"step {i:4d}  loss {losses.val:.4f} ({losses.avg:.4f})  "
                f"scale {float(scale):.0f}  "
                f"{inst:.0f} img/s ({avg:.0f} avg)")
            last_t, last_i = now, i
    if args.prof:
        from apex_tpu.utils import profiler_stop
        profiler_stop()
    if mgr is not None:
        mgr.wait()  # commit any in-flight async checkpoint
    if warm_t0 is not None and last_i > warm_i0:
        speed = global_batch * (last_i - warm_i0) / max(last_t - warm_t0,
                                                        1e-9)
    else:  # a single boundary (e.g. --steps 1): the compile-span rate
        speed = inst
    maybe_print(f"Speed: {speed:.1f} img/s total (post-warmup)")


if __name__ == "__main__":
    main()
