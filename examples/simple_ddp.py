"""Minimal data-parallel training example.

Port of the reference's ``examples/simple/distributed/
distributed_data_parallel.py``: the smallest program showing the DDP wrapper
— there, one Linear layer per process with ``torch.distributed.launch``;
here, the same model SPMD-sharded over a device mesh with
``DistributedDataParallel.reduce`` doing the flat-bucket gradient allreduce.

Run on the real chip(s), or anywhere on a virtual mesh:
    python examples/simple_ddp.py --world-size 8 --force-cpu
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.jax_compat import shard_map


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--world-size", type=int, default=0,
                   help="devices to use (0 = all available)")
    p.add_argument("--force-cpu", action="store_true",
                   help="run on a virtual CPU mesh (sets "
                        "xla_force_host_platform_device_count)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--allreduce-always-fp32", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    if args.force_cpu:
        import os
        n = args.world_size or 8
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        # config-level pin, not jax.devices("cpu"): the latter still
        # initializes every registered platform (incl. the TPU plugin,
        # which can block when the device is held elsewhere)
        jax.config.update("jax_platforms", "cpu")

    from apex_tpu.parallel import DistributedDataParallel

    devices = (jax.devices("cpu") if args.force_cpu else jax.devices())
    world = args.world_size or len(devices)
    devices = devices[:world]
    mesh = Mesh(np.array(devices), ("data",))
    print(f"world size {world} on {devices[0].platform}")

    # One linear layer, rank-varying data — the reference example's setup.
    in_dim, out_dim, per_rank = 16, 4, 32
    params = {
        "w": jnp.zeros((in_dim, out_dim), jnp.float32),
        "b": jnp.zeros((out_dim,), jnp.float32),
    }
    tx = optax.sgd(0.05)
    opt_state = tx.init(params)
    ddp = DistributedDataParallel(
        axis_name="data",
        allreduce_always_fp32=args.allreduce_always_fp32)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(world * per_rank, in_dim).astype(np.float32))
    t = jnp.asarray(rng.randn(world * per_rank, out_dim).astype(np.float32))

    def loss_fn(p, xb, tb):
        pred = xb @ p["w"] + p["b"]
        return jnp.mean(jnp.square(pred - tb))

    def train_step(p, opt_state, xb, tb):
        from apex_tpu.parallel import pvary_params
        p_local = pvary_params(p, "data")
        loss, grads = jax.value_and_grad(loss_fn)(p_local, xb, tb)
        grads = ddp.reduce(grads)          # flat-bucket mean-allreduce
        updates, opt_state = tx.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, \
            jax.lax.pmean(loss, "data")

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P())))

    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, x, t)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.6f}")


if __name__ == "__main__":
    main()
