"""BASELINE config 5: DCGAN with two optimizers and two loss scalers.

The workload the reference's stub ``examples/dcgan`` was meant to carry: a
generator and a discriminator, each with its own optimizer, trained with
*independent* dynamic loss scalers — the ``num_losses`` / ``loss_id``
machinery (``apex/amp/handle.py:53-58``).  Here each network gets its own
:class:`~apex_tpu.amp.Amp` (the functional analog of two loss_ids), so an
overflow in D's backward never shrinks G's scale.
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import optax

from apex_tpu import amp
from apex_tpu.models.dcgan import Discriminator, Generator, gan_losses


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O1")
    p.add_argument("--zdim", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--image-size", type=int, default=32,
                   choices=[32, 64])
    p.add_argument("--print-freq", type=int, default=20)
    return p.parse_args()


def main():
    args = parse_args()
    n_up = {32: 2, 64: 3}[args.image_size]
    G = Generator(feature_maps=64, n_upsample=n_up)
    D = Discriminator(feature_maps=64, n_down=n_up + 1)

    kz = jax.random.PRNGKey(0)
    z0 = jax.random.normal(kz, (2, args.zdim))
    img0 = jnp.zeros((2, args.image_size, args.image_size, 3))
    gv = G.init(jax.random.PRNGKey(1), z0, train=True)
    dv = D.init(jax.random.PRNGKey(2), img0, train=True)

    adam = lambda: optax.adam(args.lr, b1=0.5, b2=0.999)
    a_g = amp.initialize(optimizer=adam(), opt_level=args.opt_level)
    a_d = amp.initialize(optimizer=adam(), opt_level=args.opt_level)
    gs, ds = a_g.init(gv["params"]), a_d.init(dv["params"])
    g_stats, d_stats = gv["batch_stats"], dv["batch_stats"]

    # Stats are *closed over* (never passed through Amp.run's arg caster) so
    # keep_batchnorm_fp32 holds: running buffers stay fp32 under O2/O3.
    # Update cadence matches the reference DCGAN loop: per iteration G's BN
    # stats update once (G's own forward in the G step; the fake used by D
    # is a stats-frozen forward) while D's update three times (real + fake
    # in the D step, fake again in the G step).
    def make_d_loss(g_stats, d_stats):
        def d_loss(dp, gp, z, real):
            fake = G.apply({"params": gp, "batch_stats": g_stats}, z,
                           train=True, mutable=["batch_stats"])[0]
            d_real, d_mut = D.apply(
                {"params": dp, "batch_stats": d_stats}, real,
                train=True, mutable=["batch_stats"])
            d_fake, d_mut = D.apply(
                {"params": dp, "batch_stats": d_mut["batch_stats"]},
                jax.lax.stop_gradient(fake), train=True,
                mutable=["batch_stats"])
            loss, _ = gan_losses(d_real, d_fake, d_fake)
            return loss, d_mut["batch_stats"]
        return d_loss

    def make_g_loss(g_stats, d_stats):
        def g_loss(gp, dp, z):
            fake, g_mut = G.apply({"params": gp, "batch_stats": g_stats},
                                  z, train=True, mutable=["batch_stats"])
            logits, d_mut = D.apply({"params": dp, "batch_stats": d_stats},
                                    fake, train=True,
                                    mutable=["batch_stats"])
            _, loss = gan_losses(logits, logits, logits)
            return loss, (g_mut["batch_stats"], d_mut["batch_stats"])
        return g_loss

    @jax.jit
    def train_step(gs, ds, g_stats, d_stats, z, real):
        # D step (loss_id 0 of the reference's shared-model two-scaler run)
        def scaled_d(dp):
            l, stats = a_d.run(make_d_loss(g_stats, d_stats), dp,
                               a_g.model_params(gs), z, real)
            return a_d.scale_loss(l, ds), (l, stats)
        d_grads, (dl, d_stats_) = \
            jax.grad(scaled_d, has_aux=True)(a_d.model_params(ds))
        ds, d_info = a_d.apply_gradients(ds, d_grads)

        # G step (loss_id 1)
        def scaled_g(gp):
            l, stats = a_g.run(make_g_loss(g_stats, d_stats_), gp,
                               a_d.model_params(ds), z)
            return a_g.scale_loss(l, gs), (l, stats)
        g_grads, (gl, (g_stats_, d_stats_)) = \
            jax.grad(scaled_g, has_aux=True)(a_g.model_params(gs))
        gs, g_info = a_g.apply_gradients(gs, g_grads)
        return gs, ds, g_stats_, d_stats_, dl, gl, d_info, g_info

    for i in range(args.steps):
        k = jax.random.PRNGKey(100 + i)
        z = jax.random.normal(k, (args.batch_size, args.zdim))
        # synthetic "real" images: smooth blobs
        real = jnp.tanh(jax.random.normal(
            k, (args.batch_size, args.image_size, args.image_size, 3)))
        gs, ds, g_stats, d_stats, dl, gl, d_info, g_info = train_step(
            gs, ds, g_stats, d_stats, z, real)
        if i % args.print_freq == 0 or i == args.steps - 1:
            print(f"step {i:4d}  D {float(dl):.4f} G {float(gl):.4f}  "
                  f"scales D {float(d_info['loss_scale']):.0f} "
                  f"G {float(g_info['loss_scale']):.0f}")


if __name__ == "__main__":
    main()
