"""BASELINE config 4: BERT pretraining with FusedLAMB + FusedLayerNorm.

The reference ships the LAMB kernels with no driver (SURVEY.md §0); this is
the end-to-end pretraining loop those kernels exist for.  Synthetic masked-LM
data by default; ``--size large`` selects BERT-large (the v5e-16 config),
``--size large-tpu`` the same model with the TPU-native 8x128 head geometry
(same parameter count, ~20% faster steps), ``--size tiny`` runs anywhere.

Data-parallel over all devices with ``--dp`` (shard_map over ("data",)).
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.bert import (
    BertForPreTraining,
    bert_base,
    bert_large,
    bert_large_tpu,
    bert_tiny,
    pretraining_loss,
)
from apex_tpu.optimizers import fused_lamb
from apex_tpu.parallel import DistributedDataParallel, data_parallel_mesh
from apex_tpu.utils import maybe_print
from apex_tpu.utils.jax_compat import shard_map

# "large-tpu" = bert-large with the TPU-native 8x128 head geometry (same
# parameter count, ~20% faster pretraining steps on v5e)
CONFIGS = {"tiny": bert_tiny, "base": bert_base, "large": bert_large,
           "large-tpu": bert_large_tpu}


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--size", default="tiny", choices=list(CONFIGS))
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-device batch")
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--dp", action="store_true")
    p.add_argument("--print-freq", type=int, default=10)
    return p.parse_args()


def synthetic_mlm_batch(key, cfg, batch, seq_len):
    ks = jax.random.split(key, 3)
    ids = jax.random.randint(ks[0], (batch, seq_len), 0, cfg.vocab_size)
    labels = ids  # predict the original token at masked positions
    mask_pos = (jax.random.uniform(ks[1], (batch, seq_len)) < 0.15)
    masked_ids = jnp.where(mask_pos, 103, ids)  # [MASK]-style id
    nsp = jax.random.randint(ks[2], (batch,), 0, 2)
    return (masked_ids, jnp.ones((batch, seq_len), jnp.int32), labels,
            mask_pos.astype(jnp.float32), nsp)


def main():
    args = parse_args()
    cfg = CONFIGS[args.size]()
    seq_len = min(args.seq_len, cfg.max_position_embeddings)
    model = BertForPreTraining(cfg)

    batch0 = synthetic_mlm_batch(jax.random.PRNGKey(0), cfg, 2, seq_len)
    variables = model.init(jax.random.PRNGKey(1), batch0[0],
                           attention_mask=batch0[1])
    a = amp.initialize(optimizer=fused_lamb(learning_rate=args.lr),
                       opt_level=args.opt_level)
    state = a.init(variables["params"])

    def loss_fn(p, ids, mask, labels, mlm_mask, nsp):
        mlm, nspl = model.apply({"params": p}, ids, attention_mask=mask)
        return pretraining_loss(mlm, nspl, mlm_labels=labels,
                                nsp_labels=nsp, mlm_mask=mlm_mask)

    if args.dp:
        mesh = data_parallel_mesh()
        n_dev = len(jax.devices())
        ddp = DistributedDataParallel(axis_name="data")
        inner = amp.make_train_step(a, loss_fn, axis_name="data",
                                    reduce_fn=ddp.reduce)

        def sharded(s, *b):
            s2, m = inner(s, *b)
            return s2, jax.lax.pmean(m["loss"], "data")

        step = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(P(),) + (P("data"),) * 5, out_specs=(P(), P())))
    else:
        n_dev = 1
        inner = amp.make_train_step(a, loss_fn)
        step = jax.jit(lambda s, *b: (lambda r: (r[0], r[1]["loss"]))(
            inner(s, *b)))

    global_batch = args.batch_size * n_dev
    t0 = None
    for i in range(args.steps):
        batch = synthetic_mlm_batch(jax.random.PRNGKey(i + 2), cfg,
                                    global_batch, seq_len)
        state, loss = step(state, *batch)
        if i == 0:
            jax.block_until_ready(loss)
            t0 = time.time()  # exclude compile
        if i % args.print_freq == 0 or i == args.steps - 1:
            maybe_print(f"step {i:4d}  loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    if args.steps > 1:
        sps = (args.steps - 1) * global_batch / (time.time() - t0)
        maybe_print(f"Speed: {sps:.1f} sequences/s")


if __name__ == "__main__":
    main()
