"""GPT causal-LM training demo — the long-context workload.

Beyond the reference (2019-era apex has no LM / long-context story):
trains :class:`apex_tpu.models.gpt.GPTModel` on synthetic token streams
under amp O2 with FusedAdam; ``--seq-parallel`` shards the sequence over a
mesh axis with ring attention (rope positions stay global), ``--remat``
rematerializes each block for HBM headroom at long L.

Run anywhere:
    python examples/gpt_lm.py --steps 20 --seq-len 256
    python examples/gpt_lm.py --seq-parallel --devices 4 --force-cpu
On a real TPU slice, drop --force-cpu and the mesh spans the chips.
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--size", default="tiny",
                   choices=["tiny", "small", "small-tpu"],
                   help="small-tpu = gpt-small with the TPU-native 6x128 "
                        "head geometry (same params, ~30%% faster steps)")
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--scan-layers", action="store_true")
    p.add_argument("--data", default="synthetic",
                   choices=["synthetic", "pysrc"],
                   help="pysrc = byte-level LM over the Python standard "
                        "library sources (real text, available offline); "
                        "fresh random windows every step, reports "
                        "bits-per-byte and a greedy sample")
    p.add_argument("--sample-bytes", type=int, default=96,
                   help="greedy continuation length printed after "
                        "--data pysrc training")
    p.add_argument("--seq-parallel", action="store_true",
                   help="shard the sequence over a mesh axis (ring "
                        "attention)")
    p.add_argument("--devices", type=int, default=4,
                   help="mesh size for --seq-parallel")
    p.add_argument("--force-cpu", action="store_true")
    p.add_argument("--print-freq", type=int, default=10)
    return p.parse_args()


def main():
    args = parse_args()
    if args.force_cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import dataclasses
    import jax
    from apex_tpu.utils.jax_compat import shard_map
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.gpt import (
        GPTModel, gpt_small, gpt_small_tpu, gpt_tiny, lm_loss)
    from apex_tpu.optimizers import FusedAdam

    cfg = {"tiny": gpt_tiny, "small": gpt_small,
           "small-tpu": gpt_small_tpu}[args.size]()
    cfg = dataclasses.replace(cfg, remat=args.remat,
                              scan_layers=args.scan_layers)

    b, l = args.batch_size, args.seq_len
    rng = np.random.RandomState(0)
    corpus = None
    if args.data == "pysrc":
        if args.seq_parallel:
            raise SystemExit("--data pysrc supports the local path only")
        # real text available in any environment: the stdlib's own source
        corpus = _load_pysrc_corpus()
        cfg = dataclasses.replace(cfg, vocab_size=256)  # byte-level
        print(f"pysrc corpus: {len(corpus) / 1e6:.1f}M bytes")
        ids = _sample_windows(corpus, rng, b, l)
    else:
        # synthetic structured stream: next token = (token + step) %
        # vocab, so the LM has signal to fit and the loss visibly descends
        base = rng.randint(0, cfg.vocab_size, (b, 1))
        ids = jnp.asarray((base + np.arange(l)[None, :]) % cfg.vocab_size)

    a = amp.initialize(optimizer=FusedAdam(lr=args.lr),
                       opt_level=args.opt_level, verbosity=0)

    if args.seq_parallel:
        from jax.sharding import Mesh, PartitionSpec as P
        n = min(args.devices, len(jax.devices()))
        if l % n != 0:
            raise SystemExit(
                f"--seq-parallel requires --seq-len divisible by the "
                f"device count: got seq_len={l}, devices={n}")
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        cfg_sp = dataclasses.replace(cfg, seq_axis_name="seq")
        model = GPTModel(cfg_sp)
        init_model = GPTModel(cfg)   # init needs no bound mesh axis
        params = init_model.init(jax.random.PRNGKey(0), ids[:, :16])["params"]
        state = a.init(params)
        positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
        targets = jnp.roll(ids, -1, axis=1)
        mask = jnp.ones((b, l), jnp.float32).at[:, -1].set(0.0)

        def loss_fn(p, ids_sh, tgt_sh, pos_sh, m_sh):
            logits = model.apply({"params": p}, ids_sh, positions=pos_sh)
            # global normalizer: shard grads sum to the global-mean grad
            return lm_loss(logits, tgt_sh, mask=m_sh, seq_axis_name="seq")

        train = amp.make_train_step(a, loss_fn)

        def train_step(state, ids_sh, tgt_sh, pos_sh, m_sh):
            new_state, metrics = train(state, ids_sh, tgt_sh, pos_sh, m_sh)
            # each shard holds local_sum/global_count: psum = global mean
            return new_state, jax.lax.psum(metrics["loss"], "seq")

        # check_rep=False (legacy-jax only; stripped on the VMA API):
        # the legacy checker can't see the seq-axis reductions through
        # the ring-attention step (it infers replication from pvary
        # annotations that are identity there) and rejects the
        # replicated out_specs.  Safe: grad runs entirely inside the
        # body with the loss normalizer/psum explicit (see lm_loss).
        step = jax.jit(shard_map(
            train_step, mesh=mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq"),
                      P(None, "seq"), P(None, "seq")),
            out_specs=(P(), P()), check_rep=False))
        batch = (ids, targets, positions, mask)
    else:
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0), ids[:, :16])["params"]
        state = a.init(params)

        def loss_fn(p, ids):
            logits = model.apply({"params": p}, ids)
            return lm_loss(logits[:, :-1], ids[:, 1:])

        step = jax.jit(amp.make_train_step(a, loss_fn))
        batch = (ids,)

    t0 = time.perf_counter()
    for i in range(args.steps):
        if corpus is not None and i > 0:
            batch = (_sample_windows(corpus, rng, b, l),)
        state, out = step(state, *batch)
        loss = out if args.seq_parallel else out["loss"]
        if i % args.print_freq == 0 or i == args.steps - 1:
            extra = (f"  ({float(loss) / np.log(2):.3f} bits/byte)"
                     if corpus is not None else "")
            print(f"step {i:4d}  loss {float(loss):.4f}{extra}")
    dt = time.perf_counter() - t0
    tok = b * l * args.steps / dt
    print(f"done: {tok / 1e3:.1f}K tokens/s "
          f"({jax.devices()[0].platform}, seq_parallel={args.seq_parallel})")

    if corpus is not None and args.sample_bytes > 0:
        text = _greedy_sample(model, state, corpus, l, args.sample_bytes)
        print("--- greedy sample (prompt|continuation) ---")
        print(text)


def _load_pysrc_corpus(max_bytes=8 << 20):
    """Concatenated Python standard-library sources as one byte stream —
    real, structured text present in every environment (no downloads)."""
    import sysconfig
    from pathlib import Path

    root = Path(sysconfig.get_paths()["stdlib"])
    chunks, total = [], 0
    for path in sorted(root.glob("*.py")):
        try:
            data = path.read_bytes()
        except OSError:
            continue
        chunks.append(data)
        total += len(data)
        if total >= max_bytes:
            break
    import numpy as np
    return np.frombuffer(b"".join(chunks), dtype=np.uint8)


def _sample_windows(corpus, rng, b, l):
    import jax.numpy as jnp
    import numpy as np
    if len(corpus) < l + 2:
        raise SystemExit(
            f"pysrc corpus has {len(corpus)} bytes, too small for "
            f"--seq-len {l} (zipped stdlib? try a smaller sequence)")
    starts = rng.randint(0, len(corpus) - l - 1, size=b)
    return jnp.asarray(np.stack([corpus[s:s + l] for s in starts])
                       .astype(np.int32))


def _greedy_sample(model, state, corpus, l, n_bytes):
    """Greedy continuation of a corpus prompt via the KV-cached decoder
    (:func:`apex_tpu.models.generate`): one compiled prefill + scan —
    the previous sliding-window loop re-ran a full forward AND paid one
    host round trip per generated byte."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import generate

    window_len = l // 2
    prompt = jnp.asarray(corpus[:window_len].astype(np.int32))[None, :]
    out = generate(state.master_params, model.cfg, prompt, n_bytes)
    toks = np.asarray(out)[0].tolist()
    # decode prompt and continuation separately so the '|' separator
    # stays exact even when the byte boundary splits a UTF-8 sequence
    head = bytes(toks[:window_len]).decode("utf-8", errors="replace")
    tail = bytes(toks[window_len:]).decode("utf-8", errors="replace")
    return head + "|" + tail


if __name__ == "__main__":
    main()
